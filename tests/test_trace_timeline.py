"""Distributed tracing, flight recorder, and `slt trace` timelines (PR 2).

Fast tier: traceparent parse/format, ambient-context span chaining,
worker→coordinator register/heartbeat propagation with a merged timeline,
flight-recorder ring + SIGTERM dump (subprocess), skew estimation over
synthetic two-node logs, Perfetto export shape.

Slow tier: the acceptance path — a real 2-process run (coordinator daemon
+ a WorkerAgent host), `slt trace --out` over both logs producing a
Perfetto-loadable file with a cross-process parented chain, plus injected
clock skew recovered by the Cristian-pair estimator.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from serverless_learn_tpu.telemetry import flight
from serverless_learn_tpu.telemetry import timeline as tln
from serverless_learn_tpu.telemetry import tracing as ttrace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(autouse=True)
def _isolate_tracing(monkeypatch):
    """Each test gets a clean tracing/node state; the process-global sink
    must not leak spans across tests."""
    monkeypatch.setattr(ttrace, "_node", None)
    monkeypatch.setattr(ttrace, "_event_log", None)
    yield


# -- context propagation (fast) ----------------------------------------------

def test_traceparent_parse_format_roundtrip():
    ctx = ttrace.new_context()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    back = ttrace.parse_traceparent(ctx.traceparent())
    assert back == ctx
    # Robustness: malformed values parse to None, never raise.
    for bad in (None, 7, "", "hello", "00-zz-ff-01",
                "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # zero trace id
                "00-" + "a" * 32 + "-" + "0" * 16 + "-01",  # zero span id
                "ff-" + "a" * 32 + "-" + "b" * 16 + "-01"):  # forbidden ver
        assert ttrace.parse_traceparent(bad) is None, bad
    # Case/whitespace tolerant.
    assert ttrace.parse_traceparent(
        " 00-" + "A" * 32 + "-" + "b" * 16 + "-01 ") is not None


def test_span_scopes_nest_and_emit(tmp_path):
    log = tmp_path / "spans.jsonl"
    ttrace.init_tracing(node="n1", events_log=str(log),
                        install_flight=False)
    with ttrace.span("outer") as outer:
        assert ttrace.current_context().span_id == outer.span_id
        with ttrace.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    assert ttrace.current_context() is None
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    assert [r["span"] for r in recs] == ["inner", "outer"]  # emit at exit
    assert all(r["node"] == "n1" for r in recs)
    assert all("t0_unix_s" in r and "duration_s" in r for r in recs)


def test_attach_context_stamps_protobuf():
    sys.path.insert(0, os.path.join(REPO, "native", "gen"))
    import slt_pb2 as pb

    req = pb.HeartbeatRequest(worker_id=1)
    assert ttrace.attach_context(req) is None  # no ambient context: absent
    assert not req.HasField("trace")
    with ttrace.span("parent", emit=False):
        ctx = ttrace.attach_context(req)
        assert req.trace.trace_id == ctx.trace_id
        assert req.trace.span_id == ctx.span_id
        # Round-trips the wire.
        back = pb.HeartbeatRequest.FromString(req.SerializeToString())
        assert back.trace.trace_id == ctx.trace_id


# -- worker -> coordinator propagation (fast; the satellite tier-1 test) -----

def test_register_heartbeat_traceparent_chains_in_merged_timeline(tmp_path):
    """worker→coordinator register/heartbeat through control/client.py with
    an active trace: the merged timeline (worker JSONL + coordinator
    --events_log) shows a parented chain root -> client RPC span [-> the
    daemon's server-side span when the daemon logs spans]."""
    from serverless_learn_tpu.control.client import CoordinatorClient
    from serverless_learn_tpu.control.daemons import start_coordinator

    port = _free_port()
    coord_log = tmp_path / "coord.jsonl"
    worker_log = tmp_path / "worker.jsonl"
    proc = start_coordinator(port=port, lease_ttl_ms=5000, sweep_ms=100,
                             events_log=str(coord_log))
    try:
        ttrace.init_tracing(node="worker-A", events_log=str(worker_log),
                            install_flight=False)
        c = CoordinatorClient(f"127.0.0.1:{port}")
        with ttrace.span("worker/startup"):
            rep = c.register("w:1", name="w1", n_chips=1)
            assert rep.ok
            assert c.heartbeat(rep.worker_id, step=1, metric=0.5).ok
        c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=5)

    logs = [str(worker_log)]
    if coord_log.exists():  # daemon-side spans need a trace-aware daemon
        logs.append(str(coord_log))
    tl = tln.reconstruct(logs)
    traces = tl.traces()
    assert len(traces) == 1
    spans = next(iter(traces.values()))
    by_name = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s)
    root = by_name["worker/startup"][0]
    reg = [s for s in by_name["rpc/register"] if s.node == "worker-A"][0]
    hb = [s for s in by_name["rpc/heartbeat"] if s.node == "worker-A"][0]
    assert reg.parent_id == root.span_id
    assert hb.parent_id == root.span_id
    assert tln.chain_depth(spans) >= 2
    if coord_log.exists():
        srv = [s for s in by_name["rpc/register"] if s.node != "worker-A"]
        assert srv and srv[0].parent_id == reg.span_id, \
            "daemon span must parent under the client RPC span"
        assert tln.chain_depth(spans) >= 3
        assert len(tl.nodes) == 2


def test_untraced_rpcs_stay_untraced(tmp_path):
    """No ambient context and no sink => no trace field on the wire and no
    span allocations (bare library use must stay free)."""
    from serverless_learn_tpu.control.client import CoordinatorClient
    from serverless_learn_tpu.control.daemons import start_coordinator

    port = _free_port()
    coord_log = tmp_path / "coord.jsonl"
    proc = start_coordinator(port=port, events_log=str(coord_log))
    try:
        c = CoordinatorClient(f"127.0.0.1:{port}")
        rep = c.register("w:1")
        assert rep.ok and c.heartbeat(rep.worker_id).ok
        c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=5)
    assert not coord_log.exists() or coord_log.read_text() == ""


# -- flight recorder (fast) --------------------------------------------------

def test_flight_ring_is_bounded_and_dump_has_metrics(tmp_path):
    flight.set_capacity(16)
    try:
        for i in range(100):
            flight.record({"event": "x", "i": i})
        evs = flight.events()
        assert len(evs) == 16 and evs[-1]["i"] == 99 and evs[0]["i"] == 84
        path = flight.dump("unit-test", dir=str(tmp_path))
        assert path and os.path.exists(path)
        d = json.loads(open(path).read())
        assert d["reason"] == "unit-test" and len(d["events"]) == 16
        assert "metrics" in d  # registry snapshot rides along
    finally:
        flight.set_capacity(flight.DEFAULT_CAPACITY)


def test_maybe_dump_noop_until_installed(tmp_path):
    if flight.installed():
        pytest.skip("flight handlers already installed in this process")
    assert flight.maybe_dump("lease-expiry") is None
    assert not any(f.startswith("flight-") for f in os.listdir("."))


def test_sigterm_leaves_flight_dump(tmp_path):
    """Acceptance: killing a traced process with SIGTERM leaves a flight
    dump containing its last spans, and the exit code stays 143."""
    script = tmp_path / "victim.py"
    script.write_text(
        "import sys, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from serverless_learn_tpu.telemetry import init_tracing\n"
        "from serverless_learn_tpu.telemetry import tracing as ttrace\n"
        f"init_tracing(node='victim', flight_dir={str(tmp_path)!r})\n"
        "with ttrace.span('victim/work'):\n"
        "    pass\n"
        "print('ready', flush=True)\n"
        "time.sleep(60)\n")
    proc = subprocess.Popen([sys.executable, str(script)],
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "ready"
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == -signal.SIGTERM or rc == 143
    dumps = [f for f in os.listdir(tmp_path)
             if f.startswith("flight-victim-")]
    assert dumps, os.listdir(tmp_path)
    d = json.loads((tmp_path / dumps[0]).read_text())
    assert d["reason"] == "sigterm"
    assert any(e.get("span") == "victim/work" for e in d["events"])


# -- timeline reconstruction (fast) ------------------------------------------

def _synthetic_two_node_logs(tmp_path, skew_s: float):
    """Node A (client) + node B (server, clock shifted +skew_s). Returns
    (paths, client_rpc_span_bounds)."""
    t0 = 1_700_000_000.0
    a_recs = [
        {"event": "span", "span": "round", "node": "A",
         "trace_id": "t" * 32, "span_id": "a-root", "t0_unix_s": t0,
         "duration_s": 0.5},
        {"event": "span", "span": "rpc/put", "node": "A",
         "trace_id": "t" * 32, "span_id": "a-rpc", "parent_id": "a-root",
         "t0_unix_s": t0 + 0.10, "duration_s": 0.04},
    ]
    b_recs = [
        {"event": "span", "span": "rpc/put", "node": "B",
         "trace_id": "t" * 32, "span_id": "b-srv", "parent_id": "a-rpc",
         # True server time: inside the client's [0.10, 0.14] window;
         # logged on B's clock which runs ahead by skew_s.
         "t0_unix_s": t0 + 0.11 + skew_s, "duration_s": 0.02},
    ]
    pa, pb = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    pa.write_text("\n".join(json.dumps(r) for r in a_recs) + "\n")
    pb.write_text("\n".join(json.dumps(r) for r in b_recs) + "\n")
    return [str(pa), str(pb)], (t0 + 0.10, t0 + 0.14)


def test_skew_correction_recovers_injected_offset(tmp_path):
    paths, (lo, hi) = _synthetic_two_node_logs(tmp_path, skew_s=5.0)
    tl = tln.reconstruct(paths, root="A")
    assert abs(tl.offsets["B"] + 5.0) < 0.05, tl.offsets
    srv = [s for s in tl.spans if s.span_id == "b-srv"][0]
    assert lo <= srv.start and srv.end <= hi + 1e-6, (srv.start, lo, hi)
    # Without correction the server span sits 5 s in the future.
    raw = tln.reconstruct(paths, skew=False)
    srv_raw = [s for s in raw.spans if s.span_id == "b-srv"][0]
    assert srv_raw.start > hi + 4.0


def test_critical_path_attributes_self_time(tmp_path):
    paths, _ = _synthetic_two_node_logs(tmp_path, skew_s=0.0)
    tl = tln.reconstruct(paths, root="A")
    rows = tln.critical_path(next(iter(tl.traces().values())))
    by_span = {r["span_id"]: r for r in rows}
    # Root: 0.5 total minus the 0.04 covered by its child RPC.
    assert abs(by_span["a-root"]["self_s"] - 0.46) < 1e-6
    # Client RPC: 0.04 minus the server's 0.02.
    assert abs(by_span["a-rpc"]["self_s"] - 0.02) < 1e-6
    assert rows[0]["span_id"] == "a-root"  # sorted worst-first


def test_trace_events_export_is_perfetto_shaped(tmp_path):
    paths, _ = _synthetic_two_node_logs(tmp_path, skew_s=2.0)
    out = tln.to_trace_events(tln.reconstruct(paths, root="A"))
    assert set(out) >= {"traceEvents", "displayTimeUnit"}
    evs = out["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    metas = [e for e in evs if e["ph"] == "M" and e["name"] == "process_name"]
    assert len(xs) == 3 and len(metas) == 2
    assert all(e["ts"] >= 0 and e["dur"] > 0 for e in xs)
    assert {m["args"]["name"] for m in metas} == {"A", "B"}
    json.dumps(out)  # must be serializable as-is


def test_cli_trace_command_writes_timeline(tmp_path, capsys):
    from serverless_learn_tpu.cli import main

    paths, _ = _synthetic_two_node_logs(tmp_path, skew_s=1.0)
    out = tmp_path / "timeline.json"
    rc = main(["trace", *paths, "--out", str(out), "--root", "A",
               "--compact"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["traces"] == 1 and summary["spans"] == 3
    assert abs(summary["clock_offsets_s"]["B"] + 1.0) < 0.05
    assert summary["slowest_traces"][0]["chain_depth"] == 3
    data = json.loads(out.read_text())
    assert any(e.get("ph") == "X" for e in data["traceEvents"])
    # Empty input is a loud error, not an empty file.
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert main(["trace", str(empty)]) == 1


def test_flight_dump_feeds_timeline(tmp_path):
    """flight-*.json dumps merge with JSONL logs (node inherited from the
    dump header when records lack one)."""
    dump = {"event": "flight_dump", "node": "dead-worker", "reason": "x",
            "events": [
                {"event": "span", "span": "train/run",
                 "trace_id": "u" * 32, "span_id": "w-1",
                 "t0_unix_s": 1_700_000_000.0, "duration_s": 1.0},
                {"event": "train_step", "step": 3},
            ]}
    p = tmp_path / "flight-dead-worker-1.json"
    p.write_text(json.dumps(dump))
    tl = tln.reconstruct([str(tmp_path)])  # directory ingestion
    assert len(tl.spans) == 1
    assert tl.spans[0].node == "dead-worker"
    assert tl.skipped == 0  # non-span records aren't "skipped spans"


# -- acceptance (slow): 2-process run, skew injected, CLI end-to-end ---------

@pytest.mark.slow
def test_two_process_run_produces_skewed_corrected_timeline(tmp_path):
    """Acceptance: coordinator daemon + worker process, `slt trace --out`
    over both logs -> Perfetto-loadable trace_event JSON with >= 1
    cross-process parented chain and skew-corrected timestamps (the
    worker's log is rewritten with +3 s skew to prove correction)."""
    from serverless_learn_tpu.cli import main
    from serverless_learn_tpu.control.client import WorkerAgent
    from serverless_learn_tpu.control.daemons import start_coordinator

    port = _free_port()
    coord_log = tmp_path / "coord.jsonl"
    worker_log = tmp_path / "worker.jsonl"
    proc = start_coordinator(port=port, lease_ttl_ms=5000, sweep_ms=100,
                             events_log=str(coord_log))
    try:
        ttrace.init_tracing(node="worker-A", events_log=str(worker_log),
                            install_flight=False)
        agent = WorkerAgent(f"127.0.0.1:{port}", "w:1", name="w1",
                            heartbeat_interval_ms=100)
        agent.start()
        time.sleep(0.6)  # a few heartbeats
        agent.stop()
    finally:
        proc.terminate()
        proc.wait(timeout=5)
    if not coord_log.exists():
        pytest.skip("daemon predates --events_log (native binary without "
                    "trace support)")

    # Inject +3 s of clock skew into the WORKER's log after the fact.
    skewed = tmp_path / "worker_skewed.jsonl"
    with open(worker_log) as src, open(skewed, "w") as dst:
        for line in src:
            rec = json.loads(line)
            rec["t0_unix_s"] = rec["t0_unix_s"] + 3.0
            dst.write(json.dumps(rec) + "\n")

    out = tmp_path / "timeline.json"
    rc = main(["trace", str(skewed), str(coord_log), "--out", str(out),
               "--root", "worker-A", "--compact"])
    assert rc == 0
    data = json.loads(out.read_text())
    xs = [e for e in data["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) >= 2
    # Cross-process parented chain: a coordinator span whose parent is a
    # worker client span.
    tl = tln.reconstruct([str(skewed), str(coord_log)], root="worker-A")
    by_id = {s.span_id: s for s in tl.spans}
    cross = [s for s in tl.spans
             if s.parent_id and s.parent_id in by_id
             and by_id[s.parent_id].node != s.node]
    assert cross, "no cross-process parented span chain"
    # Skew-corrected: the coordinator node's offset ~= +3 s (its clock is
    # 3 s "behind" the doctored worker log) and each server span lands
    # inside its client span.
    coord_node = [n for n in tl.nodes if n != "worker-A"][0]
    assert abs(tl.offsets[coord_node] - 3.0) < 0.5, tl.offsets
    for s in cross:
        p = by_id[s.parent_id]
        assert p.start - 0.05 <= s.start <= p.end + 0.05
