"""Tracing/profiling subsystem (SURVEY.md §5 — absent in the reference,
whose only observability was std::cout narration on every RPC).

Covers: host-span aggregation, jax.profiler trace capture, and the native
daemons' per-RPC latency accounting scraped over the stats RPC.
"""

import glob
import os
import socket
import time

import pytest

from serverless_learn_tpu.utils.tracing import (
    MSG_TYPE_NAMES, Tracer, capture, get_tracer, rpc_stats, step_annotation)


def test_tracer_span_aggregation():
    tr = Tracer()
    for _ in range(3):
        with tr.span("unit/sleep", annotate_device=False):
            time.sleep(0.01)
    s = tr.summary()["unit/sleep"]
    assert s["count"] == 3
    assert s["total_s"] >= 0.03
    assert s["max_s"] >= s["mean_s"] > 0


def test_tracer_thread_safety():
    import threading

    tr = Tracer()

    def work():
        for _ in range(100):
            tr.record("x", 0.001)

    threads = [threading.Thread(target=work) for _ in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert tr.summary()["x"]["count"] == 800


def test_global_tracer_singleton():
    assert get_tracer() is get_tracer()


def test_profiler_capture(tmp_path):
    import jax
    import jax.numpy as jnp

    logdir = str(tmp_path / "trace")
    with capture(logdir):
        with step_annotation(1):
            jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
    produced = glob.glob(os.path.join(logdir, "**", "*"), recursive=True)
    assert any(os.path.isfile(p) for p in produced), "no trace files written"


def test_training_records_step_spans():
    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
    from serverless_learn_tpu.training.loop import run_training

    tr = get_tracer()
    tr.reset()
    cfg = ExperimentConfig(
        model="mlp_mnist",
        mesh=MeshConfig(dp=8),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(batch_size=16, num_steps=3),
        data=DataConfig(),
    )
    run_training(cfg)
    assert tr.summary()["train/step"]["count"] == 3


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def coordinator_addr():
    from serverless_learn_tpu.control.daemons import start_coordinator

    port = _free_port()
    proc = start_coordinator(port=port)
    yield f"127.0.0.1:{port}"
    proc.terminate()
    proc.wait(timeout=5)


def test_coordinator_rpc_latency_accounting(coordinator_addr):
    from serverless_learn_tpu.control.client import CoordinatorClient

    c = CoordinatorClient(coordinator_addr)
    r = c.register("w1:9000", name="w1")
    for _ in range(5):
        c.heartbeat(r.worker_id)
    stats = rpc_stats(c)
    c.close()
    assert stats["rpc/register"]["count"] == 1
    assert stats["rpc/heartbeat"]["count"] == 5
    hb = stats["rpc/heartbeat"]
    assert hb["max_s"] >= hb["mean_s"] > 0


def test_shard_server_rpc_latency_accounting(tmp_path):
    from serverless_learn_tpu.control.client import ShardClient
    from serverless_learn_tpu.control.daemons import start_shard_server

    port = _free_port()
    proc = start_shard_server(port=port, root=str(tmp_path))
    try:
        c = ShardClient(f"127.0.0.1:{port}")
        c.put("ds/a", b"x" * 1024)
        c.fetch("ds/a")
        stats = rpc_stats(c)
        c.close()
        assert stats["rpc/put"]["count"] == 1
        assert stats["rpc/fetch"]["count"] == 1
        assert stats["rpc/fetch"]["total_s"] > 0
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_msg_type_names_match_framing_header():
    # Names must track native/framing.h MsgType tags.
    header = open(os.path.join(os.path.dirname(__file__), os.pardir,
                               "native", "framing.h")).read()
    tags = {"register": "MSG_REGISTER_REQ = 1",
            "heartbeat": "MSG_HEARTBEAT_REQ = 3",
            "fetch": "MSG_FETCH_REQ = 22",
            "put": "MSG_PUT_REQ = 24"}
    for name, decl in tags.items():
        assert decl in header
        tag = int(decl.split("=")[1])
        assert MSG_TYPE_NAMES[tag] == name
