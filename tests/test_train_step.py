"""End-to-end train-step tests on the virtual mesh: loss decreases, DP
gradient sync is exact, and the same seed gives identical results across
mesh shapes (the gold-standard check that sharding only changes layout,
never math)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.data.datasets import SyntheticSource
from serverless_learn_tpu.training.train_step import build_trainer


def _cfg(model="mlp_mnist", mesh=None, **train_kw):
    train_kw.setdefault("batch_size", 32)
    train_kw.setdefault("num_steps", 5)
    return ExperimentConfig(
        model=model,
        mesh=mesh or MeshConfig(),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(**train_kw),
        data=DataConfig(seq_len=16),
    )


def _run_steps(cfg, n=3, devices_slice=None):
    import serverless_learn_tpu.parallel.mesh as mesh_mod

    mesh = mesh_mod.make_mesh(cfg.mesh, devices=devices_slice)
    trainer = build_trainer(cfg, mesh=mesh)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data,
                          cfg.train.batch_size, seed=123)
    losses = []
    for batch, _ in zip(iter(src), range(n)):
        state, metrics = trainer.step(state, trainer.shard_batch(batch))
        losses.append(float(metrics["loss"]))
    return state, losses


def test_mlp_overfits_fixed_batch_single_device(devices):
    import serverless_learn_tpu.parallel.mesh as mesh_mod

    cfg = _cfg(mesh=MeshConfig(dp=1))
    mesh = mesh_mod.make_mesh(cfg.mesh, devices=devices[:1])
    trainer = build_trainer(cfg, mesh=mesh)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 32, seed=123)
    batch = trainer.shard_batch(next(iter(src)))
    losses = []
    for _ in range(12):
        state, metrics = trainer.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < 0.5 * losses[0], losses


def test_dp8_matches_single_device_exactly(devices):
    """Sharding the batch over 8 devices must not change the math (fp32)."""
    kw = dict(dtype="float32")
    cfg1 = _cfg(mesh=MeshConfig(dp=1))
    cfg1 = cfg1.override(model_overrides={"dtype": jnp.float32})
    cfg8 = _cfg(mesh=MeshConfig(dp=8)).override(
        model_overrides={"dtype": jnp.float32})
    _, l1 = _run_steps(cfg1, n=4, devices_slice=devices[:1])
    _, l8 = _run_steps(cfg8, n=4)
    np.testing.assert_allclose(l1, l8, rtol=2e-5)


def test_dp_tp_matches_dp_only(devices):
    """2-way TP over the MLP must reproduce pure-DP losses (fp32)."""
    cfgA = _cfg(mesh=MeshConfig(dp=8)).override(
        model_overrides={"dtype": jnp.float32})
    cfgB = _cfg(mesh=MeshConfig(dp=4, tp=2)).override(
        model_overrides={"dtype": jnp.float32})
    _, lA = _run_steps(cfgA, n=3)
    _, lB = _run_steps(cfgB, n=3)
    np.testing.assert_allclose(lA, lB, rtol=2e-5)


def test_resnet18_step_runs_and_updates_batchstats(devices):
    cfg = _cfg(model="resnet18_cifar", mesh=MeshConfig(dp=8),
               batch_size=16, num_steps=2)
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 16, seed=0)
    batch = trainer.shard_batch(next(iter(src)))
    bs_before = jax.device_get(
        jax.tree_util.tree_leaves(state.model_state)[0])
    state, metrics = trainer.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    bs_after = jax.device_get(
        jax.tree_util.tree_leaves(state.model_state)[0])
    assert not np.allclose(bs_before, bs_after)
    assert int(jax.device_get(state.step)) == 1


def test_bert_tiny_mlm_step(devices):
    cfg = _cfg(model="bert_tiny", mesh=MeshConfig(dp=4, tp=2),
               batch_size=8, num_steps=2)
    _, losses = _run_steps(cfg, n=2)
    assert all(np.isfinite(l) for l in losses)


def test_llama_tiny_fsdp_tp(devices):
    cfg = _cfg(model="llama_tiny", mesh=MeshConfig(dp=2, fsdp=2, tp=2),
               batch_size=8, num_steps=2)
    _, losses = _run_steps(cfg, n=2)
    assert all(np.isfinite(l) for l in losses)


def test_remat_matches_no_remat(devices):
    """jax.checkpoint trades FLOPs for memory — it must not change the math."""
    base = _cfg(model="llama_tiny", mesh=MeshConfig(dp=8), batch_size=8,
                num_steps=2).override(
        model_overrides={"dtype": jnp.float32})
    _, plain = _run_steps(base, n=2)
    remat = base.override(
        model_overrides={"dtype": jnp.float32, "remat": True})
    _, checkpointed = _run_steps(remat, n=2)
    np.testing.assert_allclose(plain, checkpointed, rtol=2e-5)


def test_train_dtype_policy_reaches_model(devices):
    """train.param_dtype flows into the model unless model_overrides says
    otherwise."""
    cfg = _cfg(mesh=MeshConfig(dp=8), batch_size=16)
    cfg = cfg.override(train=TrainConfig(batch_size=16, num_steps=1,
                                         param_dtype="bfloat16"))
    trainer = build_trainer(cfg)
    state = trainer.init()
    leaves = jax.tree_util.tree_leaves(state.params)
    assert all(l.dtype == jnp.bfloat16 for l in leaves)


def test_llama_lora_freezes_base(devices):
    cfg = _cfg(model="llama_tiny", mesh=MeshConfig(dp=8), batch_size=8)
    cfg = cfg.override(model_overrides={"lora_rank": 4})
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 8, seed=0)
    p0 = jax.device_get(state.params)
    state, _ = trainer.step(state, trainer.shard_batch(next(iter(src))))
    p1 = jax.device_get(state.params)

    flat0 = jax.tree_util.tree_flatten_with_path(p0)[0]
    flat1 = {jax.tree_util.keystr(k): v
             for k, v in jax.tree_util.tree_flatten_with_path(p1)[0]}
    changed_lora = changed_base = 0
    for k, v0 in flat0:
        key = jax.tree_util.keystr(k)
        v1 = flat1[key]
        changed = not np.allclose(np.asarray(v0, np.float32),
                                  np.asarray(v1, np.float32))
        if "lora" in key:
            changed_lora += int(changed)
        else:
            changed_base += int(changed)
    assert changed_base == 0, "base params must stay frozen under LoRA"
    assert changed_lora > 0, "LoRA params must train"


# -- ZeRO update sharding (round 18) ------------------------------------------


def _zero_cfg(stage, mesh=None, name="adamw", grad_reduce="float32",
              **train_kw):
    train_kw.setdefault("batch_size", 32)
    return ExperimentConfig(
        model="mlp_mnist",
        mesh=mesh or MeshConfig(dp=8),
        optimizer=OptimizerConfig(name=name, learning_rate=1e-2),
        train=TrainConfig(zero_stage=stage, grad_reduce_dtype=grad_reduce,
                          **train_kw),
        data=DataConfig(seq_len=16),
        model_overrides={"dtype": jnp.float32},
    )


def test_zero1_matches_zero0_params_step_for_step(devices):
    """The tentpole acceptance (ISSUE 13): ZeRO-1 sharded update ==
    replicated update, step for step, at a tight ulp bound (f32 grad
    reduce re-associates the same summands) — via the ParityHarness —
    while opt-state bytes/chip shrink ~1/dp and the gauge says so."""
    from serverless_learn_tpu.telemetry.numerics import ParityHarness
    from serverless_learn_tpu.telemetry.registry import MetricsRegistry
    from serverless_learn_tpu.training.zero import (bytes_per_chip,
                                                    publish_opt_state_gauge)

    t0 = build_trainer(_zero_cfg(0))
    t1 = build_trainer(_zero_cfg(1))
    s0, s1 = t0.init(), t1.init()

    # The memory claim, measured: dp=8 shards every divisible opt leaf.
    b0, b1 = bytes_per_chip(s0.opt_state), bytes_per_chip(s1.opt_state)
    assert b1 < 0.2 * b0, (b0, b1)
    reg = MetricsRegistry()
    assert publish_opt_state_gauge(s1.opt_state, registry=reg) == b1
    # A leaf physically landed as a 1/8 slice.
    mu = [l for l in jax.tree_util.tree_leaves(s1.opt_state)
          if getattr(l, "ndim", 0) == 2 and l.shape[0] % 8 == 0][0]
    assert {s.data.shape[0] for s in mu.addressable_shards} == \
        {mu.shape[0] // 8}

    src = SyntheticSource(t0.bundle.make_batch, DataConfig(), 32, seed=123)
    batches = [b for b, _ in zip(iter(src), range(4))]
    grad_norms = []

    def ref_step(state, batch):
        state, m = t0.step(state, t0.shard_batch(batch))
        grad_norms.append(float(jax.device_get(m["grad_norm"])))
        return state, m

    def cand_step(state, batch):
        state, m = t1.step(state, t1.shard_batch(batch))
        grad_norms.append(float(jax.device_get(m["grad_norm"])))
        return state, m

    with ParityHarness(ref_step, cand_step, s0, s1) as h:
        for b in batches:
            h.step(b)
    report = h.report(rtol=1e-7, atol=1e-9)
    assert report["within_tolerance"], report
    worst_ulp = max(c["max_ulp"] for c in report["subtrees"].values())
    assert worst_ulp <= 4, report["subtrees"]
    # Norms over dp-sharded leaves stay GLOBAL: the in-graph grad_norm
    # metric agrees between layouts at every step.
    for a, b in zip(grad_norms[::2], grad_norms[1::2]):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_zero2_reduce_scatter_layout_and_parity(devices):
    """Stage 2 (gradient sharding: the dp psum becomes a reduce-scatter
    into the owned slice) is still exact vs the replicated baseline."""
    from serverless_learn_tpu.telemetry.numerics import ParityHarness

    t0 = build_trainer(_zero_cfg(0, name="sgd"))
    t2 = build_trainer(_zero_cfg(2, name="sgd"))
    src = SyntheticSource(t0.bundle.make_batch, DataConfig(), 32, seed=7)
    batches = [b for b, _ in zip(iter(src), range(3))]
    with ParityHarness(
            lambda s, b: t0.step(s, t0.shard_batch(b)),
            lambda s, b: t2.step(s, t2.shard_batch(b)),
            t0.init(), t2.init()) as h:
        for b in batches:
            h.step(b)
    report = h.report(rtol=1e-7, atol=1e-9)
    assert report["within_tolerance"], report
    assert max(c["max_ulp"] for c in report["subtrees"].values()) <= 4


def test_zero_bf16_grad_reduce_loss_curve_parity(devices):
    """grad_reduce_dtype=bf16 halves the exchange bytes; the loss curve
    must track the f32 exchange within tolerance (NOT ulp parity — the
    reduced gradient is genuinely rounded to 8 mantissa bits)."""
    losses = {}
    for key, stage, gr in (("f32", 0, "float32"), ("bf16", 2, "bf16")):
        t = build_trainer(_zero_cfg(stage, grad_reduce=gr))
        s = t.init()
        src = SyntheticSource(t.bundle.make_batch, DataConfig(), 32,
                              seed=31)
        curve = []
        for b, _ in zip(iter(src), range(6)):
            s, m = t.step(s, t.shard_batch(b))
            curve.append(float(jax.device_get(m["loss"])))
        losses[key] = curve
    assert all(np.isfinite(losses["bf16"])), losses
    np.testing.assert_allclose(losses["f32"], losses["bf16"], rtol=0.05,
                               atol=5e-3)


@pytest.mark.slow
def test_zero1_composes_with_fsdp_tp(devices):
    """ZeRO over dp composes with fsdp/tp model sharding on a
    transformer: the opt leaves carry ('dp','fsdp')-style compositions
    and training stays finite."""
    from serverless_learn_tpu.training.zero import bytes_per_chip

    cfg = _zero_cfg(1, mesh=MeshConfig(dp=2, fsdp=2, tp=2), batch_size=8)
    cfg = cfg.override(model="llama_tiny")
    t = build_trainer(cfg)
    s = t.init()
    cfg0 = _zero_cfg(0, mesh=MeshConfig(dp=2, fsdp=2, tp=2),
                     batch_size=8).override(model="llama_tiny")
    s_ref = build_trainer(cfg0).init()
    assert bytes_per_chip(s.opt_state) < 0.75 * bytes_per_chip(
        s_ref.opt_state)
    src = SyntheticSource(t.bundle.make_batch, cfg.data, 8, seed=0)
    for b, _ in zip(iter(src), range(2)):
        s, m = t.step(s, t.shard_batch(b))
        assert np.isfinite(float(jax.device_get(m["loss"])))


def test_zero_knob_validation(devices):
    import serverless_learn_tpu.parallel.mesh as mesh_mod

    with pytest.raises(ValueError, match="zero_stage"):
        build_trainer(_zero_cfg(3),
                      mesh=mesh_mod.make_mesh(MeshConfig(dp=8)))
    with pytest.raises(ValueError, match="grad_reduce_dtype"):
        build_trainer(_zero_cfg(1, grad_reduce="int8"),
                      mesh=mesh_mod.make_mesh(MeshConfig(dp=8)))
