"""End-to-end train-step tests on the virtual mesh: loss decreases, DP
gradient sync is exact, and the same seed gives identical results across
mesh shapes (the gold-standard check that sharding only changes layout,
never math)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serverless_learn_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, OptimizerConfig, TrainConfig)
from serverless_learn_tpu.data.datasets import SyntheticSource
from serverless_learn_tpu.training.train_step import build_trainer


def _cfg(model="mlp_mnist", mesh=None, **train_kw):
    train_kw.setdefault("batch_size", 32)
    train_kw.setdefault("num_steps", 5)
    return ExperimentConfig(
        model=model,
        mesh=mesh or MeshConfig(),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(**train_kw),
        data=DataConfig(seq_len=16),
    )


def _run_steps(cfg, n=3, devices_slice=None):
    import serverless_learn_tpu.parallel.mesh as mesh_mod

    mesh = mesh_mod.make_mesh(cfg.mesh, devices=devices_slice)
    trainer = build_trainer(cfg, mesh=mesh)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data,
                          cfg.train.batch_size, seed=123)
    losses = []
    for batch, _ in zip(iter(src), range(n)):
        state, metrics = trainer.step(state, trainer.shard_batch(batch))
        losses.append(float(metrics["loss"]))
    return state, losses


def test_mlp_overfits_fixed_batch_single_device(devices):
    import serverless_learn_tpu.parallel.mesh as mesh_mod

    cfg = _cfg(mesh=MeshConfig(dp=1))
    mesh = mesh_mod.make_mesh(cfg.mesh, devices=devices[:1])
    trainer = build_trainer(cfg, mesh=mesh)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 32, seed=123)
    batch = trainer.shard_batch(next(iter(src)))
    losses = []
    for _ in range(12):
        state, metrics = trainer.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < 0.5 * losses[0], losses


def test_dp8_matches_single_device_exactly(devices):
    """Sharding the batch over 8 devices must not change the math (fp32)."""
    kw = dict(dtype="float32")
    cfg1 = _cfg(mesh=MeshConfig(dp=1))
    cfg1 = cfg1.override(model_overrides={"dtype": jnp.float32})
    cfg8 = _cfg(mesh=MeshConfig(dp=8)).override(
        model_overrides={"dtype": jnp.float32})
    _, l1 = _run_steps(cfg1, n=4, devices_slice=devices[:1])
    _, l8 = _run_steps(cfg8, n=4)
    np.testing.assert_allclose(l1, l8, rtol=2e-5)


def test_dp_tp_matches_dp_only(devices):
    """2-way TP over the MLP must reproduce pure-DP losses (fp32)."""
    cfgA = _cfg(mesh=MeshConfig(dp=8)).override(
        model_overrides={"dtype": jnp.float32})
    cfgB = _cfg(mesh=MeshConfig(dp=4, tp=2)).override(
        model_overrides={"dtype": jnp.float32})
    _, lA = _run_steps(cfgA, n=3)
    _, lB = _run_steps(cfgB, n=3)
    np.testing.assert_allclose(lA, lB, rtol=2e-5)


def test_resnet18_step_runs_and_updates_batchstats(devices):
    cfg = _cfg(model="resnet18_cifar", mesh=MeshConfig(dp=8),
               batch_size=16, num_steps=2)
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 16, seed=0)
    batch = trainer.shard_batch(next(iter(src)))
    bs_before = jax.device_get(
        jax.tree_util.tree_leaves(state.model_state)[0])
    state, metrics = trainer.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    bs_after = jax.device_get(
        jax.tree_util.tree_leaves(state.model_state)[0])
    assert not np.allclose(bs_before, bs_after)
    assert int(jax.device_get(state.step)) == 1


def test_bert_tiny_mlm_step(devices):
    cfg = _cfg(model="bert_tiny", mesh=MeshConfig(dp=4, tp=2),
               batch_size=8, num_steps=2)
    _, losses = _run_steps(cfg, n=2)
    assert all(np.isfinite(l) for l in losses)


def test_llama_tiny_fsdp_tp(devices):
    cfg = _cfg(model="llama_tiny", mesh=MeshConfig(dp=2, fsdp=2, tp=2),
               batch_size=8, num_steps=2)
    _, losses = _run_steps(cfg, n=2)
    assert all(np.isfinite(l) for l in losses)


def test_remat_matches_no_remat(devices):
    """jax.checkpoint trades FLOPs for memory — it must not change the math."""
    base = _cfg(model="llama_tiny", mesh=MeshConfig(dp=8), batch_size=8,
                num_steps=2).override(
        model_overrides={"dtype": jnp.float32})
    _, plain = _run_steps(base, n=2)
    remat = base.override(
        model_overrides={"dtype": jnp.float32, "remat": True})
    _, checkpointed = _run_steps(remat, n=2)
    np.testing.assert_allclose(plain, checkpointed, rtol=2e-5)


def test_train_dtype_policy_reaches_model(devices):
    """train.param_dtype flows into the model unless model_overrides says
    otherwise."""
    cfg = _cfg(mesh=MeshConfig(dp=8), batch_size=16)
    cfg = cfg.override(train=TrainConfig(batch_size=16, num_steps=1,
                                         param_dtype="bfloat16"))
    trainer = build_trainer(cfg)
    state = trainer.init()
    leaves = jax.tree_util.tree_leaves(state.params)
    assert all(l.dtype == jnp.bfloat16 for l in leaves)


def test_llama_lora_freezes_base(devices):
    cfg = _cfg(model="llama_tiny", mesh=MeshConfig(dp=8), batch_size=8)
    cfg = cfg.override(model_overrides={"lora_rank": 4})
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = SyntheticSource(trainer.bundle.make_batch, cfg.data, 8, seed=0)
    p0 = jax.device_get(state.params)
    state, _ = trainer.step(state, trainer.shard_batch(next(iter(src))))
    p1 = jax.device_get(state.params)

    flat0 = jax.tree_util.tree_flatten_with_path(p0)[0]
    flat1 = {jax.tree_util.keystr(k): v
             for k, v in jax.tree_util.tree_flatten_with_path(p1)[0]}
    changed_lora = changed_base = 0
    for k, v0 in flat0:
        key = jax.tree_util.keystr(k)
        v1 = flat1[key]
        changed = not np.allclose(np.asarray(v0, np.float32),
                                  np.asarray(v1, np.float32))
        if "lora" in key:
            changed_lora += int(changed)
        else:
            changed_base += int(changed)
    assert changed_base == 0, "base params must stay frozen under LoRA"
    assert changed_lora > 0, "LoRA params must train"
