"""Request waterfalls (round 21): the per-request lifecycle ledger, its
attribution contract, the `slt waterfall` merge/decomposition pipeline,
router hop provenance, and the static engine's reduced ledger.

The attribution contract under test: interval causes (compile,
harvest_drain) claim their measured overlap with a stalled gap (scaled
down when they over-explain); marker causes (preempt, prefill_steal,
kv_exhausted, compaction — any 0-width event) split the leftover excess;
a bare residual lands in "other". Per stall, base_s + sum(causes) must
equal the measured gap — `summarize` re-checks that invariant over every
record it merges, and the smoke acceptance at the bottom proves the
whole thing end to end on a live engine with constructed faults.
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from serverless_learn_tpu.telemetry import waterfall
from serverless_learn_tpu.telemetry.registry import (
    JsonlEventLog, MetricsRegistry, Span)
from serverless_learn_tpu.telemetry.waterfall import (
    BoundaryEvents, RequestWaterfall)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "waterfall",
                       "waterfall_fixture.jsonl")
BENCH_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                             "waterfall", "bench_history_waterfall.json")


# -- builder units -----------------------------------------------------------


def test_boundary_overlap_intervals_clip_and_markers_flag():
    ev = BoundaryEvents()
    ev.note("compile", 10.0, 11.0)        # interval
    ev.note("preempt", 10.5)              # marker (0-width)
    ev.note("compile", 20.0, 21.0)        # outside the probed window
    ov = ev.overlap(10.4, 10.8)
    # The interval's overlap is clipped to the window; the marker shows
    # up as a 0.0 presence flag (it claims residual, not overlap).
    assert ov["compile"] == pytest.approx(0.4, abs=1e-9)
    assert ov["preempt"] == 0.0
    ov2 = ev.overlap(30.0, 31.0)
    assert ov2 == {}


def test_note_decode_stall_invariant_and_baseline_isolation():
    """A gap stalled behind a compile interval: causes sum to the
    above-baseline excess (base_s + sum == gap), and the EWMA baseline
    is NOT polluted by the stalled gap (the next stall still trips)."""
    wf = RequestWaterfall(min_stall_s=0.001)
    ev = BoundaryEvents()
    t = 100.0
    wf.first_token(t)
    for _ in range(8):                    # steady 10ms baseline
        t += 0.010
        assert wf.note_decode(t, 1, ev) is not None
    base_before = wf.itl_ewma
    ev.note("compile", t + 0.002, t + 0.055)
    t += 0.060                            # 60ms gap, ~50ms excess
    itl, causes = wf.note_decode(t, 1, ev)
    assert causes is not None and "compile" in causes
    stall = wf.stalls[-1]
    assert stall["base_s"] + sum(stall["causes"].values()) == \
        pytest.approx(stall["gap_s"], abs=2e-6)
    assert wf.itl_ewma == base_before     # stalled gap excluded from EWMA
    t += 0.060                            # same stall again, still trips
    _, causes2 = wf.note_decode(t, 1, ev)
    assert causes2 is not None


def test_markers_split_residual_and_bare_residual_is_other():
    wf = RequestWaterfall(min_stall_s=0.001)
    ev = BoundaryEvents()
    t = 0.0
    wf.first_token(t)
    for _ in range(6):
        t += 0.010
        wf.note_decode(t, 1, ev)
    # Two markers inside the stalled gap: the excess splits evenly.
    ev.note("preempt", t + 0.01)
    ev.note("prefill_steal", t + 0.02)
    t += 0.050
    _, causes = wf.note_decode(t, 1, ev)
    assert set(causes) == {"preempt", "prefill_steal"}
    assert causes["preempt"] == pytest.approx(causes["prefill_steal"])
    # No event at all inside the next stalled gap -> "other".
    t += 0.050
    _, causes = wf.note_decode(t, 1, ev)
    assert set(causes) == {"other"}


def test_interval_overclaim_is_scaled_to_excess():
    """An interval longer than the gap's excess must not over-explain:
    its claim is scaled down so the breakdown still sums to excess."""
    wf = RequestWaterfall(min_stall_s=0.001)
    ev = BoundaryEvents()
    t = 0.0
    wf.first_token(t)
    for _ in range(6):
        t += 0.010
        wf.note_decode(t, 1, ev)
    ev.note("harvest_drain", t - 0.5, t + 0.5)  # covers the whole gap
    t += 0.040
    _, causes = wf.note_decode(t, 1, ev)
    stall = wf.stalls[-1]
    assert set(causes) == {"harvest_drain"}
    assert sum(causes.values()) == pytest.approx(
        stall["gap_s"] - stall["base_s"], abs=1e-9)


def test_finalize_ttft_decomposition_is_exact():
    span = Span("request")
    wf = RequestWaterfall()
    span.marks["admit"] = 0.010
    span.marks["first_token"] = 0.120
    span.marks["done"] = 0.200
    wf.note_admit(0.0, 0.004)             # durations, absolute ts irrelevant
    wf.note_compile(0.0, 0.050)
    rec = wf.finalize(span)
    d = rec["ttft_decomp_s"]
    assert d["queue"] == pytest.approx(0.010, abs=1e-6)
    assert d["compile"] == pytest.approx(0.050, abs=1e-6)
    assert d["admit"] == pytest.approx(0.004, abs=1e-6)
    # Exact by construction: prefill is the remainder.
    assert d["queue"] + d["admit"] + d["compile"] + d["prefill"] == \
        pytest.approx(rec["ttft_s"], abs=5e-6)
    assert [p["phase"] for p in rec["phases"]] == \
        ["queue", "admit", "compile", "prefill", "decode"]


# -- fixture pipeline (merge / decompose / self-check) -----------------------


def test_fixture_merges_engine_and_router_records():
    rep = waterfall.report([FIXTURE])
    reqs = waterfall.merge_requests(waterfall.read_records([FIXTURE]))
    merged = [r for r in reqs if r.get("waterfall") and r.get("router")]
    assert merged, "no trace carried both engine + router records"
    s = rep["summary"]
    inv = s["invariants"]
    assert inv["ttft_decomp_bad"] == 0 and inv["stall_sum_bad"] == 0
    assert s["dominant_stall_cause"]
    assert s["itl"]["p99_s"] >= s["itl"]["p50_s"]
    # Router rollup saw the fixture's hedge and shed entries.
    assert s["router"]["hedged"] >= 1
    assert s["router"]["sheds"] >= 1


def test_self_check_passes_on_synthetic_and_committed_fixture():
    for rep in (waterfall.self_check(),
                waterfall.self_check(fixture_path=FIXTURE)):
        bad = [c for c in rep["checks"] if not c["ok"]]
        assert rep["ok"] and not bad, bad


def test_bench_rows_carry_attribution_columns():
    rep = waterfall.report([FIXTURE])
    rows = {r["metric"]: r for r in
            waterfall.bench_rows(rep["summary"])}
    itl = rows["serve_itl_p99_ms"]
    ttft = rows["serve_ttft_p99_ms"]
    assert itl["value"] > 0 and "prefill_interference_frac" in itl
    for k in ("ttft_decomp_queue_ms", "ttft_decomp_admit_ms",
              "ttft_decomp_compile_ms", "ttft_decomp_prefill_ms"):
        assert k in ttft, k
    # The committed history built from these rows passes its own gate.
    from serverless_learn_tpu.telemetry import benchgate

    gate = benchgate.run_gate(BENCH_FIXTURE, metric="serve_")
    assert gate["ok"], gate


def test_render_shows_phases_and_stall_causes():
    out = waterfall.render(waterfall.report([FIXTURE]))
    for needle in ("TTFT", "ITL", "stall", "queue", "prefill"):
        assert needle in out, needle


# -- static engine: reduced ledger, TTFT == latency --------------------------


def test_static_engine_ttft_is_latency_with_reduced_waterfall(tmp_path):
    """Run-to-completion groups deliver first and last token together,
    so the static engine's TTFT histogram IS its latency histogram, and
    its waterfall is the reduced set: queue/admit/compile/generate with
    no decode phase and no decode trace."""
    from serverless_learn_tpu.inference.batching import BatchingEngine
    from serverless_learn_tpu.models.registry import get_model

    bundle = get_model("llama_tiny", dtype=jnp.float32,
                       param_dtype=jnp.float32, max_seq_len=64)
    params = bundle.module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    events = tmp_path / "events.jsonl"
    log = JsonlEventLog(str(events))
    reg = MetricsRegistry()
    eng = BatchingEngine(bundle.module, params, registry=reg,
                         event_log=log)
    try:
        for _ in range(2):                # cold group, then warm
            rep = eng.submit([3, 5, 7, 9], max_new=4, temperature=0.0,
                             top_k=0, eos_id=None, seed=0)
            assert "new_tokens" in rep, rep
    finally:
        eng.stop()
        log.close()
    snap = reg.snapshot()

    def hist(name):
        s = snap[name]["series"][0]
        return s["count"], s["sum"]

    ttft_n, ttft_sum = hist("slt_request_ttft_seconds")
    lat_n, lat_sum = hist("slt_request_latency_seconds")
    assert ttft_n == lat_n == 2
    assert ttft_sum == pytest.approx(lat_sum)

    recs = [r for r in waterfall.read_records([str(events)])
            if isinstance(r.get("waterfall"), dict)]
    assert len(recs) == 2
    cold, warm = sorted(recs, key=lambda r: r["t0_unix_s"])
    for r in (cold, warm):
        wf = r["waterfall"]
        names = [p["phase"] for p in wf["phases"]]
        assert names == ["queue", "admit", "compile", "generate"]
        assert "itl" not in wf and "gaps" not in wf and "stalls" not in wf
        d = wf["ttft_decomp_s"]
        assert sum(d.values()) == pytest.approx(wf["ttft_s"], abs=5e-6)
    # The cold group charges the jit wall to compile; the warm one not.
    assert cold["waterfall"]["ttft_decomp_s"]["compile"] > 0.0
    assert warm["waterfall"]["ttft_decomp_s"]["compile"] == 0.0
    # `slt waterfall` accepts a pure-static log (no decode trace at all).
    s = waterfall.report([str(events)])["summary"]
    assert s["requests"] == 2
    assert s["invariants"]["ttft_decomp_bad"] == 0


# -- router hop provenance ---------------------------------------------------


def _make_router(replicas, registry=None, events=None, **cfg_kw):
    from serverless_learn_tpu.config import FleetConfig
    from serverless_learn_tpu.fleet.router import FleetRouter

    defaults = dict(health_interval_s=0.15, dead_after_probes=2,
                    discover_interval_s=0.3, hedge_min_delay_s=0.05,
                    eject_s=0.4, upstream_timeout_s=5.0,
                    queue_timeout_s=1.0)
    defaults.update(cfg_kw)
    return FleetRouter(config=FleetConfig(**defaults), host="127.0.0.1",
                       port=0, replicas=tuple(replicas),
                       registry=registry or MetricsRegistry(),
                       emit=(events.append if events is not None
                             else lambda rec: None))


def _hops(events):
    return [e for e in events if e.get("event") == "waterfall_hop"]


def test_router_stamps_hop_record(tmp_path):
    from serverless_learn_tpu.fleet.testing import stub_server
    from serverless_learn_tpu.inference.server import request

    r1 = stub_server()
    events = []
    router = _make_router([r1.addr], events=events, hedge=False).start()
    try:
        time.sleep(0.3)
        rep = request(router.addr, {"prompt": [1, 2], "max_new_tokens": 2})
        assert "tokens" in rep
        deadline = time.monotonic() + 3.0
        while not _hops(events) and time.monotonic() < deadline:
            time.sleep(0.02)
        (hop,) = _hops(events)
        assert hop["trace_id"] and len(hop["trace_id"]) == 32
        assert hop["shed"] is False and hop["hedged"] is False
        assert hop["retries"] == 0
        assert hop["primary"] == hop["replica"] == r1.addr
        assert hop["total_s"] > 0 and hop["queue_wait_s"] >= 0
    finally:
        router.stop(), r1.stop()


def test_router_hedge_winner_loser_and_wasted_seconds():
    """A hedged request's hop names winner and loser; once the losing
    attempt drains, its burned seconds land in the hop and in
    slt_router_hedge_wasted_seconds_total."""
    import hashlib

    from serverless_learn_tpu.fleet.testing import StubEngine, stub_server
    from serverless_learn_tpu.inference.server import request

    slow = StubEngine(latency_s=0.6)
    r1, r2 = stub_server(engine=slow), stub_server()
    reg = MetricsRegistry()
    events = []
    router = _make_router([r1.addr, r2.addr], registry=reg,
                          events=events).start()
    try:
        time.sleep(0.3)
        session = next(       # pin the primary pick to the SLOW replica
            s for s in (f"s{i}" for i in range(64))
            if max((r1.addr, r2.addr), key=lambda a: hashlib.md5(
                f"{s}|{a}".encode()).hexdigest()) == r1.addr)
        rep = request(router.addr, {"prompt": [4], "max_new_tokens": 2,
                                    "session": session}, timeout=10)
        assert "tokens" in rep
        # The hop is emitted only after the losing attempt drains.
        deadline = time.monotonic() + 5.0
        while not _hops(events) and time.monotonic() < deadline:
            time.sleep(0.05)
        (hop,) = _hops(events)
        assert hop["hedged"] is True
        assert hop["primary"] == r1.addr
        assert hop["hedge_winner"] == r2.addr       # the hedge won
        assert hop["hedge_loser"] == r1.addr
        assert hop["hedge_wasted_s"] >= 0.3         # the slow reply burned
        assert hop["hedge_cancel_s"] >= 0.0
        fam = reg.snapshot()["slt_router_hedge_wasted_seconds_total"]
        assert sum(s["value"] for s in fam["series"]) >= 0.3
    finally:
        router.stop(), r1.stop(), r2.stop()


def test_top_renders_itl_stalls_pane():
    """The ITL/STALLS pane appears when an endpoint serves the decode
    trace metrics — stringly-typed names pinned here (SLT002 checks the
    catalog; this checks the render path end to end)."""
    from serverless_learn_tpu.telemetry import top as top_mod
    from serverless_learn_tpu.telemetry.exporter import MetricsExporter

    reg = MetricsRegistry()
    h = reg.histogram("slt_decode_itl_seconds", "itl")
    for v in (0.004, 0.005, 0.006, 0.030):
        h.observe(v)
    reg.counter("slt_decode_stall_seconds_total", "s",
                cause="compile").inc(0.9)
    reg.gauge("slt_prefill_interference_frac", "f").set(0.07)
    exp = MetricsExporter(registry=reg).start()
    try:
        st = top_mod.EndpointState(exp.addr)
        st.poll()
        out = top_mod.render([st])
        # /stalls serves the same rollup for non-screen consumers.
        stalls = json.loads(top_mod.fetch_text(exp.addr, path="/stalls"))
    finally:
        exp.stop()
    assert "ITL/STALLS" in out
    assert "compile=0.90s" in out
    assert stalls["enabled"] and stalls["itl"]["count"] == 4
    assert stalls["stall_s"] == {"compile": 0.9}
    assert stalls["prefill_interference_frac"] == pytest.approx(0.07)


# -- acceptance: live engine with constructed faults -------------------------


@pytest.mark.slow
def test_waterfall_smoke_names_injected_causes(tmp_path):
    """The round-21 acceptance, measured on a live continuous engine:
    pool overflow forces preemption, outgrown warm shapes force a
    mid-decode compile — both BY CONSTRUCTION — and the waterfalls must
    name each cause on the correct requests, with decompositions that
    sum, <2% ledger overhead, doctor naming the dominant cause from the
    JSONL alone, and gate-passing bench rows."""
    from serverless_learn_tpu.fleet.loadgen import run_waterfall_smoke

    history = tmp_path / "bench_history.json"
    rep = run_waterfall_smoke(seed=0, history_path=str(history))
    failed = [c for c in rep["checks"] if not c["ok"]]
    assert rep["ok"], failed
    rows = json.loads(history.read_text())
    assert {r["metric"] for r in rows} == \
        {"serve_itl_p99_ms", "serve_ttft_p99_ms"}
