"""Blockwise int8/fp8 wire codec (round 20, training/wire_codec.py).

Property-style pins on the codec itself (the integration tests live with
their consumers: test_diloco_dcn.py, test_elastic_mesh.py, test_herd.py):

* round-trip error bounded per block by the block max times the q-step;
* exact for zeros, deterministic half-to-even on ties, byte-identical
  re-encodes;
* NaN/Inf refused with the TYPED error (quarantine semantics depend on
  the refusal — a silently flushed NaN would make the leader's gate
  cosmetic);
* the in-graph fake-quantize path equals the host path bit-for-bit, and
  vmap-over-clients equals a python loop (the herd's determinism
  contract);
* error feedback drives the long-run mean error far below the
  feedback-free control;
* integer leaves ride verbatim; legacy (uncompressed state-dict) blobs
  still decode — mixed-dtype fleets interoperate.
"""

import numpy as np
import pytest
from flax import serialization

from serverless_learn_tpu.training import wire_codec as wc

RNG = np.random.default_rng(7)


def _rand_tree(scale=1.0):
    return {"dense": {"kernel":
                      (scale * RNG.standard_normal((129, 7))
                       ).astype(np.float32),
                      "bias": np.zeros((5,), np.float32)},
            "count": np.int32(9)}


@pytest.mark.parametrize("dtype", ["int8", "fp8"])
@pytest.mark.parametrize("block", [32, 128, 256])
def test_roundtrip_error_bounded_by_block_max_qstep(dtype, block):
    if dtype == "fp8" and not wc.fp8_supported():
        pytest.skip("no fp8 runtime")
    x = (RNG.standard_normal(1000) * np.geomspace(1e-3, 10, 1000)
         ).astype(np.float32)
    q, scales = wc.quantize_array(x, dtype, block)
    back = wc.dequantize_array(q, scales, dtype, x.shape, np.float32,
                               block)
    # per-BLOCK bound: |err| <= amax_b * qstep (qstep = 1/127 int8;
    # fp8-e4m3 has 3 mantissa bits -> rel step 1/8 of the scale window)
    qstep = 1.0 / 127 if dtype == "int8" else 1.0 / 8
    nblocks = len(scales)
    for b in range(nblocks):
        blk = x[b * block:(b + 1) * block]
        err = np.abs(back[b * block:(b + 1) * block] - blk)
        assert err.max() <= np.abs(blk).max() * qstep + 1e-12, (b, dtype)


def test_zeros_exact_and_ties_round_half_even():
    x = np.zeros(300, np.float32)
    q, s = wc.quantize_array(x, "int8", 128)
    assert (s == 0).all()
    assert (wc.dequantize_array(q, s, "int8", x.shape, np.float32, 128)
            == 0).all()
    # scale pins to 1.0 (amax 127); 63.5 and 62.5 are exact ties
    x = np.array([127.0, 63.5, 62.5, -63.5], np.float32)
    q, s = wc.quantize_array(x, "int8", 4)
    np.testing.assert_array_equal(q.view(np.int8), [127, 64, 62, -64])


def test_reencode_is_byte_identical():
    tree = _rand_tree()
    assert wc.encode(tree, "int8", 128) == wc.encode(tree, "int8", 128)


def test_nonfinite_rejected_with_typed_error():
    for bad in (np.nan, np.inf, -np.inf):
        tree = {"w": np.array([1.0, bad, 2.0], np.float32)}
        with pytest.raises(wc.NonFiniteError) as ei:
            wc.encode(tree, "int8")
        assert isinstance(ei.value, ValueError)  # typed, catchable
        assert "w" in ei.value.path
    # the f32 wire wrapping refuses nothing (it IS the fallback)
    wc.encode({"w": np.array([np.nan], np.float32)}, "f32")


def test_integer_leaves_and_template_mapping_exact():
    tree = _rand_tree()
    out = wc.decode(wc.encode(tree, "int8"), template=tree)
    assert out["count"] == tree["count"]
    assert out["count"].dtype == np.int32
    assert out["dense"]["kernel"].shape == (129, 7)
    assert out["dense"]["kernel"].dtype == np.float32


def test_decoded_twin_matches_receiver_decode():
    """encode_with_decoded's sender-side twin (the error-feedback base)
    must equal what a receiver decodes from the bytes — bit for bit."""
    tree = _rand_tree()
    for dtype in ("int8", "fp8") if wc.fp8_supported() else ("int8",):
        blob, dec = wc.encode_with_decoded(tree, dtype, 64)
        rt = wc.decode(blob, template=tree)
        np.testing.assert_array_equal(rt["dense"]["kernel"],
                                      dec["dense"]["kernel"])


def test_host_equals_in_graph_path():
    """int8: the host and in-graph paths agree bit-for-bit (same
    half-even rounding). fp8: XLA's f32->f8e4m3 convert rounds borderline
    values differently from ml_dtypes' direct cast (double-rounding in
    its lowering), so the paths agree only to one fp8 quantization step
    — acceptable because no value stream ever crosses paths (real
    islands are host-only, the herd sim is graph-only)."""
    import jax.numpy as jnp

    x = RNG.standard_normal(500).astype(np.float32)
    q, s = wc.quantize_array(x, "int8", 64)
    host = wc.dequantize_array(q, s, "int8", x.shape, np.float32, 64)
    graph = np.asarray(wc.fake_quantize(jnp.asarray(x), "int8", 64))
    np.testing.assert_array_equal(host, graph)
    if wc.fp8_supported():
        q, s = wc.quantize_array(x, "fp8", 64)
        host = wc.dequantize_array(q, s, "fp8", x.shape, np.float32, 64)
        graph = np.asarray(wc.fake_quantize(jnp.asarray(x), "fp8", 64))
        # one fp8 spacing at the top of the scale window is
        # scale * 448 / 8; allow two of them for the borderline cases
        tol = np.repeat(s, 64)[:500] * (2 * 448.0 / 8)
        assert (np.abs(host - graph) <= tol + 1e-12).all()


def test_vmap_equals_loop():
    import jax
    import jax.numpy as jnp

    xs = jnp.asarray(RNG.standard_normal((6, 85)).astype(np.float32))
    v = jax.vmap(lambda a: wc.fake_quantize(a, "int8", 32))(xs)
    loop = jnp.stack([wc.fake_quantize(xs[i], "int8", 32)
                      for i in range(xs.shape[0])])
    np.testing.assert_array_equal(np.asarray(v), np.asarray(loop))


def test_fake_quantize_propagates_nan_per_block():
    """The in-graph path can't raise: a block touched by NaN decodes as
    all-NaN (the quarantine gate reads dequantized values), and every
    other block stays clean."""
    import jax.numpy as jnp

    x = np.ones(64, np.float32)
    x[3] = np.nan
    out = np.asarray(wc.fake_quantize(jnp.asarray(x), "int8", 32))
    assert np.isnan(out[:32]).all()
    assert np.array_equal(out[32:], np.ones(32, np.float32))


def test_error_feedback_unbiases_the_stream():
    x = {"w": (0.01 * RNG.standard_normal(2000)).astype(np.float32)}
    ef = wc.ErrorFeedback("int8", 128)
    ctl = wc.ErrorFeedback("int8", 128, enabled=False)
    acc_ef = np.zeros(2000, np.float64)
    acc_ctl = np.zeros(2000, np.float64)
    for _ in range(40):
        acc_ef += wc.decode(ef.encode(x), template=x)["w"]
        acc_ctl += wc.decode(ctl.encode(x), template=x)["w"]
    err_ef = np.abs(acc_ef / 40 - x["w"]).max()
    err_ctl = np.abs(acc_ctl / 40 - x["w"]).max()
    assert err_ef < 0.2 * err_ctl, (err_ef, err_ctl)


def test_error_feedback_residual_survives_nonfinite_refusal():
    ef = wc.ErrorFeedback("int8", 128)
    x = {"w": RNG.standard_normal(300).astype(np.float32)}
    ef.encode(x)
    resid = {k: v.copy() for k, v in ef.residual.items()}
    with pytest.raises(wc.NonFiniteError):
        ef.encode({"w": np.full(300, np.nan, np.float32)})
    np.testing.assert_array_equal(ef.residual["w"], resid["w"])
    assert np.isfinite(ef.residual["w"]).all()


def test_legacy_blob_decodes_and_wire_bytes_shrink():
    tree = {"w": RNG.standard_normal((64, 32)).astype(np.float32)}
    legacy = serialization.msgpack_serialize(
        serialization.to_state_dict(tree))
    out = wc.decode(legacy, template=tree)
    np.testing.assert_array_equal(out["w"], tree["w"])
    assert wc.blob_dtype(legacy) == "float32"
    blob = wc.encode(tree, "int8", 128)
    assert wc.blob_dtype(blob) == "int8"
    assert len(legacy) > 3.5 * len(blob), (len(legacy), len(blob))
    # and the metadata estimators agree with reality within framing slop
    assert abs(wc.wire_nbytes(tree, "int8", 128) - len(blob)) < 0.1 * \
        len(blob)
    assert abs(wc.logical_nbytes(tree) - len(legacy)) < 0.1 * len(legacy)


def test_dtype_normalization_and_gating():
    assert wc.normalize_dtype("f32") == "float32"
    assert wc.normalize_dtype("INT8") == "int8"
    assert wc.normalize_dtype("fp8_e4m3") == "fp8"
    with pytest.raises(ValueError):
        wc.normalize_dtype("int4")
    if not wc.fp8_supported():
        with pytest.raises(wc.WireCodecError):
            wc.require_supported("fp8")
