"""`slt xray` hardware attribution + DCN byte accounting
(`telemetry/xray.py`, `telemetry/dcn.py`; round 16).

Fast tier: classifier coverage over the known op-name inventory, parser
determinism against the committed fixture capture (a sanitized tiny-model
run — `tests/fixtures/xray/make_fixture.py` regenerates it), roofline
math on fabricated op costs, the attribution-sums-to-total invariant,
exposed-collective interval math, mesh-axis recovery, doctor verdicts
from a capture alone, the benchgate attribution columns, the /goodput
xray section + `slt top` HW pane, and the DCN counter round-trip through
all three instrumented consumers (remesh store wiring, ReplicatedStore
peer pushes, and a real one-round DiLoCo island).

The acceptance test profiles a REAL tiny-model training run on the CPU
tier-1 path and requires >= 95% of device-event time attributed to a
taxonomy class with the per-step breakdown summing to the goodput
ledger's step time within 5%.
"""

import glob
import json
import os
import socket
import tempfile
import threading

import pytest

from serverless_learn_tpu.telemetry import dcn, xray
from serverless_learn_tpu.telemetry.registry import (MetricsRegistry,
                                                     get_registry)

FIXTURE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures", "xray", "tiny-train")
EXPECTED = os.path.join(os.path.dirname(FIXTURE_DIR),
                        "expected_summary.json")


# -- classifier --------------------------------------------------------------

def test_classifier_coverage_on_known_op_names():
    """Every op-name family the traces actually contain classifies into a
    non-unknown taxonomy class — including suffixed instances, async
    halves, and underscore-named fusions."""
    expect = {
        "dot.3": "compute", "fusion.12": "compute",
        "convolution.2": "compute", "tanh.4": "compute",
        "reduce-window": "compute", "custom-call.1": "compute",
        "convert_convert_fusion": "compute",
        "slice_concatenate_fusion.7": "compute",
        "all-reduce.3": "collective", "all-reduce-start.1": "collective",
        "all-reduce-done.1": "collective", "reduce-scatter": "collective",
        "all-gather.9": "collective", "collective-permute.2": "collective",
        "all-to-all": "collective", "send.1": "collective",
        "recv-done.4": "collective",
        "copy.4": "copy", "copy-start.2": "copy", "copy-done.2": "copy",
        "transpose.8": "copy", "bitcast-convert.1": "copy",
        "dynamic-update-slice.9": "copy",
        "infeed.5": "host", "outfeed-done.2": "host",
        "%fusion.3": "compute",
    }
    got = {name: xray.classify_op(name) for name in expect}
    assert got == expect
    # An unreadable name is unknown, not silently compute.
    assert xray.classify_op("TfrtCpuExecutable::Execute") == "unknown"


def test_collective_axis_recovery():
    axes = {"dp": 8, "fsdp": 2, "tp": 2}
    arg = {"long_name": "replica_groups={{0,1,2,3,4,5,6,7}}"}
    assert xray.collective_axis(arg, axes) == "dp"
    two = {"long_name": "replica_groups={{0,1},{2,3}}"}
    # Ambiguous: fsdp and tp both have size 2 -> not recoverable.
    assert xray.collective_axis(two, axes) is None
    assert xray.collective_axis(two, {"dp": 4, "tp": 2}) == "tp"
    assert xray.collective_axis({}, axes) is None
    assert xray.collective_axis(arg, None) is None


# -- parser determinism + fixture drift --------------------------------------

def test_parser_determinism_on_fixture():
    files = xray.find_trace_files(FIXTURE_DIR)
    assert files, f"fixture capture missing under {FIXTURE_DIR}"
    a = [xray.load_device_events(xray._read_json(fp)) for fp in files]
    b = [xray.load_device_events(xray._read_json(fp)) for fp in files]
    assert a == b
    s1 = xray.analyze_dir(FIXTURE_DIR)
    s2 = xray.analyze_dir(FIXTURE_DIR)
    assert s1 == s2


def test_fixture_matches_committed_summary():
    """The committed expected summary IS the drift gate `slt xray
    --self-check` enforces in CI; keep the test and the CLI in
    agreement."""
    with open(EXPECTED) as f:
        want = json.load(f)
    got = xray.analyze_dir(FIXTURE_DIR)
    assert {k: got.get(k) for k in want} == want
    # The fixture is a real capture of a ledger-bracketed run: the
    # stamped ledger's per-step time agrees with the trace's.
    assert 0.95 <= got["ledger_step_agreement"] <= 1.05
    assert got["coverage_frac"] >= 0.95
    assert got["per_collective_s"].get("all-reduce@dp", 0) > 0


def test_self_check_green():
    rep = xray.self_check()
    assert rep["ok"], rep["checks"]


# -- attribution invariants --------------------------------------------------

def test_attribution_sums_to_total():
    s = xray.analyze_events(xray.synthetic_events())
    summed = sum(r["seconds"] for r in s["classes"].values())
    assert abs(summed - s["device_time_s"]) < 1e-12
    for st in s["steps"]["per_step"]:
        assert abs(st["busy_s"] + st["idle_s"] - st["wall_s"]) < 1e-12
    # And on the real fixture capture:
    f = xray.analyze_dir(FIXTURE_DIR)
    summed = sum(r["seconds"] for r in f["classes"].values())
    assert abs(summed - f["device_time_s"]) < 1e-6 * max(
        1.0, f["device_time_s"])


def test_exposed_collective_interval_math():
    """A collective fully overlapped by compute is NOT exposed; a
    collective with nothing concurrent is fully exposed; a half-overlap
    splits exactly."""
    def ev(name, ts, dur):
        base = xray.op_base(name)
        return {"lane": "0/1", "name": name, "base": base,
                "class": xray.classify_op(base), "axis": None,
                "ts_us": float(ts), "dur_us": float(dur),
                "module": "jit_step"}

    events = [
        ev("dot.1", 0.0, 100.0),
        ev("all-reduce.2", 0.0, 100.0),    # fully overlapped
        ev("all-gather.3", 100.0, 100.0),  # fully exposed
        ev("dot.4", 200.0, 50.0),
        ev("reduce-scatter.5", 200.0, 100.0),  # half exposed
    ]
    s = xray.analyze_events(events)
    assert abs(s["exposed_comms_frac"] * s["window_s"] - 150e-6) < 1e-12


# -- roofline ----------------------------------------------------------------

def test_roofline_math_on_fabricated_costs():
    peak_f, peak_b = 100e12, 1e12  # ridge = 100 FLOPs/byte

    def ev(name, dur_us, flops, nbytes):
        base = xray.op_base(name)
        return {"lane": "0/1", "name": name, "base": base,
                "class": xray.classify_op(base), "axis": None,
                "ts_us": 0.0, "dur_us": dur_us, "module": "m",
                "flops": flops, "bytes": nbytes}

    events = [
        # 1e9 FLOPs in 20us at AI 1e5: roofline time 10us -> eff 0.5.
        ev("dot.1", 20.0, 1e9, 1e4),
        # 1e9 bytes in 2000us at AI 0.1: roofline 1000us -> eff 0.5.
        ev("fusion.2", 2000.0, 1e8, 1e9),
        ev("tanh.3", 30.0, None, None),  # uncosted: excluded
    ]
    roof = xray.roofline_verdicts(events, peak_f, peak_b)
    assert roof["n_costed"] == 2
    assert roof["ridge_flops_per_byte"] == 100.0
    by_op = {r["op"]: r for r in roof["ops"]}
    assert by_op["dot"]["bound"] == "compute-bound"
    assert by_op["fusion"]["bound"] == "hbm-bound"
    assert abs(by_op["dot"]["roofline_efficiency"] - 0.5) < 1e-6
    assert abs(by_op["fusion"]["roofline_efficiency"] - 0.5) < 1e-6
    # Time-weighted: 2000us of 2020us costed time is hbm-bound.
    assert abs(roof["hbm_bound_frac"] - 2000.0 / 2020.0) < 1e-6
    # No peaks -> no verdicts, never a guess.
    assert xray.roofline_verdicts(events, None, None) == {"n_costed": 0}

    mod = xray.module_roofline(1e12, 1e9, 0.02, peak_f, peak_b)
    assert mod["bound"] == "compute-bound"
    assert abs(mod["achieved_vs_roofline"] - 0.5) < 1e-6
    assert xray.module_roofline(None, 1e9, 0.02, peak_f, peak_b) is None


# -- acceptance: profiled tiny-model run vs the goodput ledger ---------------

def test_tiny_train_attribution_agrees_with_ledger(tmp_path):
    """The round-16 acceptance: on a profiled tiny-model training run
    (CPU tier-1 path), >= 95% of captured device-event time lands in a
    taxonomy class and the per-step breakdown sums to the goodput
    ledger's step time within 5%."""
    import jax

    from serverless_learn_tpu.config import (DataConfig, ExperimentConfig,
                                             MeshConfig, OptimizerConfig,
                                             TrainConfig)
    from serverless_learn_tpu.data.datasets import SyntheticSource
    from serverless_learn_tpu.telemetry import profiler
    from serverless_learn_tpu.telemetry.goodput import PhaseLedger
    from serverless_learn_tpu.training.train_step import build_trainer

    n_dev = len(jax.devices())
    cfg = ExperimentConfig(
        model="mlp_mnist",
        mesh=MeshConfig(dp=n_dev),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(batch_size=1024),
        data=DataConfig(),
    )
    trainer = build_trainer(cfg)
    state = trainer.init()
    src = iter(SyntheticSource(trainer.bundle.make_batch, cfg.data,
                               cfg.train.batch_size, seed=0))
    batch = trainer.shard_batch(next(src))
    ledger = PhaseLedger(emit=False)
    ledger.ensure_started()
    with ledger.phase("compile"):
        state, m = trainer.step(state, batch)
        float(jax.device_get(m["loss"]))
    n_steps = 4
    out = str(tmp_path / "capture")
    with profiler.capture_session(out):
        for _ in range(n_steps):
            with ledger.phase("step"):
                state, m = trainer.step(state, batch)
                float(jax.device_get(m["loss"]))
    s = xray.analyze_dir(out, n_devices=n_dev)
    assert s["coverage_frac"] >= 0.95, s["classes"]
    assert s["steps"]["n"] == n_steps
    led_step = ledger.report()["phases"]["step"]["seconds"]
    assert led_step > 0
    ratio = s["steps"]["total_wall_s"] / led_step
    assert 0.95 <= ratio <= 1.05, (s["steps"], led_step)
    # The verdict names SOMETHING, and the breakdown is non-degenerate.
    assert s["verdict"]
    assert s["classes"].get("compute", {}).get("seconds", 0) > 0


# -- doctor ------------------------------------------------------------------

def test_doctor_names_plateau_cause_from_capture_alone():
    from serverless_learn_tpu.telemetry import doctor

    rep = doctor.diagnose(xray_dirs=[FIXTURE_DIR])
    verdict = rep["summary"]["verdict"]
    assert f"xray[{FIXTURE_DIR}]" in verdict
    assert rep["xray"][0]["summary"]["verdict"] in verdict


def test_doctor_reads_stamped_capture_meta(tmp_path):
    """A capture-meta.json with an xray stamp feeds the verdict without
    re-analysis — the alert-triggered capture path."""
    from serverless_learn_tpu.telemetry import doctor

    meta = {"event": "profile_capture", "reason": "alert:stale.train_step",
            "xray": {"verdict": "step is 31% exposed all-reduce on the "
                                "dp axis", "exposed_comms_frac": 0.31}}
    p = tmp_path / "capture-meta.json"
    p.write_text(json.dumps(meta))
    rep = doctor.diagnose(paths=[str(p)])
    assert "31% exposed all-reduce on the dp axis" in \
        rep["summary"]["verdict"]


# -- DCN byte accounting -----------------------------------------------------

def _dcn_bytes(consumer, registry=None):
    rows = dcn.snapshot(registry)
    for r in rows:
        if r["consumer"] == consumer:
            return r["tx_bytes"] + r["rx_bytes"]
    return 0.0


def test_instrument_store_counts_data_calls_only():
    from serverless_learn_tpu.training.checkpoint import LocalStore

    reg = MetricsRegistry()
    with tempfile.TemporaryDirectory() as root:
        store = dcn.instrument_store(LocalStore(root), "diloco",
                                     registry=reg)
        store.put("a/b", b"x" * 1000)
        assert store.get("a/b") == b"x" * 1000
        assert store.get_range("a/b", 0, 100) == b"x" * 100
        store.exists("a/b")
        store.list("a")
        rows = {r["consumer"]: r for r in dcn.snapshot(reg)}
        assert rows["diloco"]["tx_bytes"] == 1000
        assert rows["diloco"]["rx_bytes"] == 1100
        assert rows["diloco"]["transfers"] == 3
        assert rows["diloco"]["bandwidth_bytes_per_s"] is None or \
            rows["diloco"]["bandwidth_bytes_per_s"] > 0
        # Idempotent wrapping: same consumer never double-counts.
        again = dcn.instrument_store(store, "diloco", registry=reg)
        assert again is store
        # restore_sources re-wraps so failover reads stay attributed.
        label, src = store.restore_sources()[0]
        assert isinstance(src, dcn.InstrumentedStore)


def test_dcn_roundtrip_replica_push():
    """ReplicatedStore's async peer push (consumer=replica_push) counts
    bytes on the process registry."""
    from serverless_learn_tpu.training.checkpoint import LocalStore
    from serverless_learn_tpu.training.replicate import ReplicatedStore

    before = _dcn_bytes("replica_push")
    with tempfile.TemporaryDirectory() as root:
        peer = LocalStore(os.path.join(root, "peer"))
        rs = ReplicatedStore(LocalStore(os.path.join(root, "primary")),
                             peers=[peer], fanout=1)
        rs.put("ckpt/step-1", b"y" * 2048)
        assert rs.flush(timeout_s=10.0)
        rs.close()
    assert _dcn_bytes("replica_push") >= before + 2048


def test_dcn_roundtrip_remesh_store_wiring():
    """ElasticTrainer wires its checkpoint store through the remesh
    meter: bytes moved via the wrapped store count under
    consumer=remesh."""
    from serverless_learn_tpu.config import ExperimentConfig
    from serverless_learn_tpu.training.checkpoint import LocalStore
    from serverless_learn_tpu.training.elastic import ElasticTrainer

    before = _dcn_bytes("remesh")
    with tempfile.TemporaryDirectory() as root:
        et = ElasticTrainer(ExperimentConfig(model="mlp_mnist"),
                            LocalStore(root))
        et.ckpt.store.put("elastic/step-1", b"z" * 4096)
        assert et.ckpt.store.get("elastic/step-1") == b"z" * 4096
    assert _dcn_bytes("remesh") >= before + 2 * 4096


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_dcn_roundtrip_diloco_one_round():
    """A real one-island DiLoCo round: the delta PUT and anchor GET cross
    the instrumented store and land in slt_dcn_bytes_total
    {consumer=diloco}."""
    import jax

    from serverless_learn_tpu.config import (
        DataConfig, ExperimentConfig, LocalSGDConfig, MeshConfig,
        OptimizerConfig, TrainConfig)
    from serverless_learn_tpu.control.daemons import start_coordinator
    from serverless_learn_tpu.data.datasets import SyntheticSource
    from serverless_learn_tpu.parallel.mesh import make_mesh
    from serverless_learn_tpu.training.checkpoint import LocalStore
    from serverless_learn_tpu.training.diloco_dcn import DilocoIsland

    cfg = ExperimentConfig(
        model="mlp_mnist",
        mesh=MeshConfig(dp=1),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainConfig(batch_size=16, donate_state=False),
        data=DataConfig(),
        local_sgd=LocalSGDConfig(outer="average", inner_steps=1,
                                 outer_lr=1.0, outer_momentum=0.0))
    port = _free_port()
    proc = start_coordinator(port=port, lease_ttl_ms=1500, sweep_ms=100)
    before = _dcn_bytes("diloco")
    try:
        mesh = make_mesh(cfg.mesh, devices=[jax.devices()[0]])

        def source_factory(wid):
            from serverless_learn_tpu.models.registry import get_model

            bundle = get_model(cfg.model, **cfg.model_overrides)
            return iter(SyntheticSource(bundle.make_batch, cfg.data,
                                        cfg.train.batch_size, seed=7))

        with tempfile.TemporaryDirectory() as root:
            isl = DilocoIsland(cfg, LocalStore(root),
                               f"127.0.0.1:{port}", "xraydcn", mesh=mesh,
                               source_factory=source_factory,
                               round_timeout_s=8.0)
            report = isl.run_rounds(1)
            isl.stop()
        assert report.rounds_done == 1
    finally:
        proc.terminate()
        proc.wait(timeout=5)
    assert _dcn_bytes("diloco") > before


# -- /goodput + slt top ------------------------------------------------------

def test_goodput_endpoint_serves_xray_section():
    from serverless_learn_tpu.telemetry.exporter import (MetricsExporter,
                                                         fetch_text)

    summary = xray.analyze_events(xray.synthetic_events(),
                                  device_kind="TPU v5 lite")
    xray.set_last_summary(summary)
    srv = MetricsExporter(registry=MetricsRegistry()).start()
    try:
        gp = json.loads(fetch_text(srv.addr, "/goodput"))
        assert gp["xray"]["verdict"] == summary["verdict"]
        assert gp["xray"]["exposed_comms_frac"] == \
            summary["exposed_comms_frac"]
    finally:
        srv.stop()
        xray.set_last_summary(None)


def test_top_renders_hw_pane():
    """`slt top --once` renders the HW pane from the /goodput xray
    section and the per-consumer DCN bandwidth gauges."""
    import io

    from serverless_learn_tpu.telemetry.exporter import MetricsExporter
    from serverless_learn_tpu.telemetry.top import run_top

    reg = MetricsRegistry()
    dcn.record_transfer("diloco", "tx", 10_000_000, 1.0, registry=reg)
    dcn.record_transfer("remesh", "rx", 2_000_000, 1.0, registry=reg)
    xray.set_last_summary(xray.analyze_events(
        xray.synthetic_events(), device_kind="TPU v5 lite"))
    srv = MetricsExporter(registry=reg).start()
    try:
        out = io.StringIO()
        assert run_top([srv.addr], once=True, stream=out) == 0
        text = out.getvalue()
        assert "HW" in text
        assert "diloco=10.0MB/s" in text
        assert "remesh=2.0MB/s" in text
        assert "exposed all-reduce" in text
    finally:
        srv.stop()
        xray.set_last_summary(None)


# -- benchgate attribution columns -------------------------------------------

def test_benchgate_attribution_columns():
    from serverless_learn_tpu.telemetry import benchgate

    base = {"metric": "resnet18_cifar_train_samples_per_sec_per_chip",
            "device_kind": "TPU v5 lite", "batch_per_chip": 8192}
    history = [dict(base, value=34000.0, exposed_comms_frac=0.10,
                    hw_util=0.80)]
    flat = dict(base, value=34100.0, exposed_comms_frac=0.12, hw_util=0.78)
    check = benchgate.gate_entry(flat, history)
    assert check["ok"], check
    # Collectives newly exposed: same throughput, +20pts exposed -> fail.
    worse = dict(base, value=34100.0, exposed_comms_frac=0.30,
                 hw_util=0.80)
    check = benchgate.gate_entry(worse, history)
    assert not check["ok"]
    assert any(a["column"] == "exposed_comms_frac" and not a["ok"]
               for a in check["attribution"])
    # Hardware got lazier: hw_util collapse fails even with value flat.
    lazy = dict(base, value=34100.0, hw_util=0.50)
    check = benchgate.gate_entry(lazy, history)
    assert not check["ok"]
    # Rows predating the columns neither gate nor mask.
    old = dict(base, value=34100.0)
    assert benchgate.gate_entry(old, history)["ok"]
    assert benchgate.gate_entry(
        dict(base, value=34100.0, exposed_comms_frac=0.5),
        [dict(base, value=34000.0)])["ok"]


def test_bench_gate_dry_run_covers_attribution_history(tmp_path):
    """The CI shape: `slt bench --gate --dry-run` over a history whose
    rows carry attribution columns — green when flat, red when the
    latest row exposes collectives."""
    from serverless_learn_tpu.telemetry.benchgate import run_gate

    base = {"metric": "resnet18_cifar_train_samples_per_sec_per_chip",
            "device_kind": "TPU v5 lite", "batch_per_chip": 8192}
    good = [dict(base, value=34000.0, exposed_comms_frac=0.10),
            dict(base, value=34100.0, exposed_comms_frac=0.11)]
    p = tmp_path / "hist.json"
    p.write_text(json.dumps(good))
    assert run_gate(str(p))["ok"]
    bad = good[:1] + [dict(base, value=34100.0, exposed_comms_frac=0.40)]
    p.write_text(json.dumps(bad))
    rep = run_gate(str(p))
    assert not rep["ok"]
    assert rep["regressions"]


# -- registry hygiene --------------------------------------------------------

def test_dcn_metrics_on_global_registry_render():
    """The instrumented consumers write the process registry; the
    Prometheus rendering must carry the consumer/direction labels `slt
    top` drills into."""
    dcn.record_transfer("replica_push", "tx", 123, 0.01)
    text = get_registry().render_prometheus()
    assert 'slt_dcn_bytes_total{consumer="replica_push",direction="tx"}' \
        in text
    assert "slt_dcn_effective_bandwidth_bytes_per_s" in text
